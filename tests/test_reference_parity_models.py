"""Forward-pass cross-check: our flax models vs the ACTUAL reference
torch models under IDENTICAL weights.

The reference's ``simple_models.py`` is definition-only and importable
(torch CPU); nothing is copied.  Each case initialises the torch model,
maps its parameters leaf-by-leaf into our layout — OIHW conv kernels ->
HWIO, ``[out, in]`` linear -> ``[in, out]``, and the conv->fc boundary's
flatten permutation (torch flattens NCHW so fc1's input order is
(C, H, W); flax flattens NHWC so ours is (H, W, C)) — then asserts the
two forwards agree on random input.  This pins down layout conventions,
activation choices (ELU), pooling, padding, the BatchNorm eval path, and
the TapConv stem (vs torch's true dilated convs) in one go.

Skipped when /root/reference or torch is unavailable.
"""

from __future__ import annotations

import numpy as np
import pytest

from _reference_bootstrap import reference_module

torch, ref_models = reference_module("simple_models")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from federated_pytorch_test_tpu.models import (  # noqa: E402
    ContextgenCNN,
    EncoderCNN,
    Net,
    Net1,
    Net2,
    PredictorCNN,
    ResNet9,
    ResNet18,
)
from federated_pytorch_test_tpu.utils import blocks as blocklib  # noqa: E402
from federated_pytorch_test_tpu.utils import codec  # noqa: E402


def _torch_flat(tnet, first_fc=None, chw=None) -> np.ndarray:
    """Flatten torch params in enumeration order, each leaf transformed
    to our layout first (so the segment ravels match our leaves)."""
    segs = []
    for name, p in tnet.named_parameters():
        w = p.detach().numpy().astype(np.float32)
        if w.ndim == 4:                       # conv OIHW -> HWIO
            w = np.transpose(w, (2, 3, 1, 0))
        elif w.ndim == 2:                     # linear [out, in] -> [in, out]
            if name == first_fc:
                C, H, W = chw                 # flatten-permutation boundary
                w = (w.reshape(w.shape[0], C, H, W)
                     .transpose(2, 3, 1, 0)
                     .reshape(H * W * C, w.shape[0]))
            else:
                w = w.T
        segs.append(w.ravel())
    return np.concatenate(segs)


def _load_into_ours(model, params, flat: np.ndarray):
    order = model.param_order()
    mask = blocklib.build_mask(
        jax.tree.map(lambda _: 0, params),
        blocklib.block_paths(order, [0, len(order) - 1]))
    assert codec.masked_size(params, order, mask) == flat.size, (
        "parameter count mismatch vs the reference enumeration")
    return codec.put_trainable_values(params, order, mask,
                                      jnp.asarray(flat))


def _check(tnet, model, x_nchw, first_fc=None, chw=None, atol=1e-4,
           apply_kwargs=None, out_nchw=False):
    tnet.eval()
    with torch.no_grad():
        want = tnet(torch.tensor(x_nchw)).numpy()
    if out_nchw:                    # conv-shaped torch output -> NHWC
        want = np.transpose(want, (0, 2, 3, 1))
    x = jnp.asarray(np.transpose(x_nchw, (0, 2, 3, 1)))
    params, batch_stats = model.init_variables(jax.random.PRNGKey(0), x,
                                               **(apply_kwargs or {}))
    params = _load_into_ours(model, params, _torch_flat(tnet, first_fc, chw))
    variables = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
    got = model.apply(variables, x, **(apply_kwargs or {}))
    np.testing.assert_allclose(np.asarray(got), want, rtol=0, atol=atol)


def _x(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


@pytest.mark.parametrize("tcls,ours,first_fc,chw", [
    (ref_models.Net, Net, "fc1.weight", (16, 5, 5)),
    (ref_models.Net1, Net1, "fc1.weight", (64, 5, 5)),
    (ref_models.Net2, Net2, "fc1.weight", (512, 2, 2)),
])
def test_classifier_forward_matches_reference(tcls, ours, first_fc, chw):
    torch.manual_seed(11)
    _check(tcls(), ours(), _x((4, 3, 32, 32)), first_fc=first_fc, chw=chw,
           apply_kwargs={"train": False})


@pytest.mark.parametrize("tfac,ours", [
    (ref_models.ResNet9, ResNet9),
    (ref_models.ResNet18, ResNet18),
])
def test_resnet_forward_matches_reference(tfac, ours):
    # after avg_pool the flat axis is channels-only: no fc permutation
    torch.manual_seed(13)
    _check(tfac(), ours(), _x((4, 3, 32, 32)), atol=2e-4,
           apply_kwargs={"train": False})


def test_cpc_encoder_matches_reference():
    """Also pins TapConv (im2col stem) against torch's TRUE dilated
    convolutions, independently of lax.conv_general_dilated."""
    torch.manual_seed(17)
    _check(ref_models.EncoderCNN(latent_dim=64), EncoderCNN(latent_dim=64),
           _x((4, 8, 32, 32)), atol=1e-4)


def test_cpc_contextgen_matches_reference():
    torch.manual_seed(19)
    _check(ref_models.ContextgenCNN(latent_dim=32),
           ContextgenCNN(latent_dim=32), _x((2, 32, 3, 3)), atol=1e-5,
           out_nchw=True)


def test_cpc_predictor_matches_reference():
    torch.manual_seed(23)
    tnet = ref_models.PredictorCNN(latent_dim=32, reduced_dim=8)
    model = PredictorCNN(latent_dim=32, reduced_dim=8)
    lat_nchw = _x((2, 32, 3, 3))
    ctx_nchw = _x((2, 32, 3, 3), seed=1)
    tnet.eval()
    with torch.no_grad():
        want_rl, want_pred = tnet(torch.tensor(lat_nchw),
                                  torch.tensor(ctx_nchw))
    lat = jnp.asarray(np.transpose(lat_nchw, (0, 2, 3, 1)))
    ctx = jnp.asarray(np.transpose(ctx_nchw, (0, 2, 3, 1)))
    params, _ = model.init_variables(jax.random.PRNGKey(0), lat, ctx)
    params = _load_into_ours(model, params, _torch_flat(tnet))
    got_rl, got_pred = model.apply({"params": params}, lat, ctx)
    for got, want in ((got_rl, want_rl), (got_pred, want_pred)):
        np.testing.assert_allclose(
            np.asarray(got), np.transpose(want.numpy(), (0, 2, 3, 1)),
            rtol=0, atol=1e-5)


# ----------------------------------------------------------------------
# VAE (C5): encode/decode cross-checked separately (the reparam draw is
# RNG-backend-specific by design; its math is exercised via decode on a
# fixed z).  Two extra layout mappings appear here: fc3's OUTPUT units
# are permuted (torch reshapes its 384-vector to (C,H,W)=(96,2,2), ours
# to (H,W,C)), and torch ConvTranspose2d(k=4,s=2,p=1) equals flax
# ConvTranspose(SAME) with the SPATIALLY FLIPPED kernel (verified to
# 1e-7; the conventions differ by a rot180).
# ----------------------------------------------------------------------

def _perm_in_384(w):
    """[out, 384+tail]: permute the conv-feature block of input columns
    from (C,H,W)=(96,2,2) to (H,W,C), keep any tail columns (e.g. the
    concatenated e_k), -> flax [in, out]."""
    out = w.shape[0]
    head = (w[:, :384].reshape(out, 96, 2, 2).transpose(2, 3, 1, 0)
            .reshape(384, out))
    return np.concatenate([head, w[:, 384:].T], axis=0)


def _perm_out_384(w):
    """[out=384, in]: permute the OUTPUT units (C,H,W)->(H,W,C), -> flax
    [in, out]."""
    return (w.reshape(96, 2, 2, w.shape[1]).transpose(1, 2, 0, 3)
            .reshape(384, w.shape[1]).T)


def _perm_out_384_bias(b):
    return b.reshape(96, 2, 2).transpose(1, 2, 0).ravel()


def _vae_family_flat(tnet, in_perm, out_perm) -> np.ndarray:
    """Flatten a torch (clustering-)VAE's params into our layout.
    ``in_perm``: fc names whose INPUT columns start with the 384
    conv-feature block; ``out_perm``: fc names whose OUTPUT units feed
    the (96,2,2) deconv reshape."""
    segs = []
    for name, p in tnet.named_parameters():
        w = p.detach().numpy().astype(np.float32)
        stem = name.split(".")[0]
        if name.startswith("tconv") and w.ndim == 4:
            # torch [in, out, kh, kw] -> flax [kh, kw, in, out], rot180
            w = np.transpose(w, (2, 3, 0, 1))[::-1, ::-1]
        elif w.ndim == 4:                     # conv OIHW -> HWIO
            w = np.transpose(w, (2, 3, 1, 0))
        elif stem in in_perm and name.endswith(".weight"):
            w = _perm_in_384(w)
        elif stem in out_perm and name.endswith(".weight"):
            w = _perm_out_384(w)
        elif stem in out_perm:                # the matching bias
            w = _perm_out_384_bias(w)
        elif w.ndim == 2:
            w = w.T
        segs.append(w.ravel())
    return np.concatenate(segs)


def test_vae_encode_decode_match_reference():
    from federated_pytorch_test_tpu.models import AutoEncoderCNN

    torch.manual_seed(29)
    tnet = ref_models.AutoEncoderCNN()
    tnet.eval()
    model = AutoEncoderCNN()
    x_nchw = _x((3, 3, 32, 32))
    z_np = _x((3, 10), seed=2)
    with torch.no_grad():
        want_mu, want_logvar = tnet.encode(torch.tensor(x_nchw))
        want_dec = tnet.decode(torch.tensor(z_np)).numpy()
    x = jnp.asarray(np.transpose(x_nchw, (0, 2, 3, 1)))
    params, _ = model.init_variables(jax.random.PRNGKey(0), x,
                                     jax.random.PRNGKey(1))
    params = _load_into_ours(model, params, _vae_family_flat(
        tnet, in_perm={"fc1"}, out_perm={"fc3"}))
    got_mu, got_logvar = model.apply({"params": params}, x,
                                     method=model.encode)
    np.testing.assert_allclose(np.asarray(got_mu), want_mu.numpy(),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_logvar), want_logvar.numpy(),
                               rtol=0, atol=1e-5)
    got_dec = model.apply({"params": params}, jnp.asarray(z_np),
                          method=model.decode)
    np.testing.assert_allclose(np.asarray(got_dec),
                               np.transpose(want_dec, (0, 2, 3, 1)),
                               rtol=0, atol=1e-5)


# ----------------------------------------------------------------------
# Clustering VAE (C6): the deterministic submodels encodeclus / encode /
# decode are cross-checked directly.  Extra boundaries beyond the plain
# VAE: fc21's INPUT is concat([conv-features(384), e_k(K)]) so only its
# first 384 input columns take the flatten permutation, and fc25 is the
# fc->deconv output-permutation boundary.
# ----------------------------------------------------------------------

def test_vae_cl_submodels_match_reference():
    from federated_pytorch_test_tpu.models import AutoEncoderCNNCL

    K, L = 10, 32
    torch.manual_seed(31)
    tnet = ref_models.AutoEncoderCNNCL(K=K, L=L)
    tnet.eval()
    model = AutoEncoderCNNCL(K=K, L=L)
    x_nchw = _x((3, 3, 32, 32))
    ek_np = np.eye(K, dtype=np.float32)[[2, 7, 4]]   # one-hot rows
    z_np = _x((3, L), seed=4)
    with torch.no_grad():
        want_clus = tnet.encodeclus(torch.tensor(x_nchw)).numpy()
        want_mu, want_sig2 = tnet.encode(torch.tensor(x_nchw),
                                         torch.tensor(ek_np))
        want_dec = [t.numpy() for t in tnet.decode(torch.tensor(ek_np),
                                                   torch.tensor(z_np))]
    x = jnp.asarray(np.transpose(x_nchw, (0, 2, 3, 1)))
    params, _ = model.init_variables(jax.random.PRNGKey(0), x,
                                     jax.random.PRNGKey(1))
    params = _load_into_ours(model, params, _vae_family_flat(
        tnet, in_perm={"fc11", "fc21"}, out_perm={"fc25"}))
    v = {"params": params}

    got_clus = model.apply(v, x, method=model.encodeclus)
    np.testing.assert_allclose(np.asarray(got_clus), want_clus,
                               rtol=0, atol=1e-5)
    got_mu, got_sig2 = model.apply(v, x, jnp.asarray(ek_np),
                                   method=model.encode)
    np.testing.assert_allclose(np.asarray(got_mu), want_mu.numpy(),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_sig2), want_sig2.numpy(),
                               rtol=0, atol=1e-5)
    got_dec = model.apply(v, jnp.asarray(ek_np), jnp.asarray(z_np),
                          method=model.decode)
    # mu_b, sig2_b are [B, L]; mu_th, sig2_th are conv-shaped
    for got, want, conv in zip(got_dec, want_dec,
                               (False, False, True, True)):
        want = np.transpose(want, (0, 2, 3, 1)) if conv else want
        np.testing.assert_allclose(np.asarray(got), want, rtol=0,
                                   atol=1e-5)
