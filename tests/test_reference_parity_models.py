"""Forward-pass cross-check: our flax models vs the ACTUAL reference
torch models under IDENTICAL weights.

The reference's ``simple_models.py`` is definition-only and importable
(torch CPU); nothing is copied.  Each case initialises the torch model,
maps its parameters leaf-by-leaf into our layout — OIHW conv kernels ->
HWIO, ``[out, in]`` linear -> ``[in, out]``, and the conv->fc boundary's
flatten permutation (torch flattens NCHW so fc1's input order is
(C, H, W); flax flattens NHWC so ours is (H, W, C)) — then asserts the
two forwards agree on random input.  This pins down layout conventions,
activation choices (ELU), pooling, padding, the BatchNorm eval path, and
the TapConv stem (vs torch's true dilated convs) in one go.

Skipped when /root/reference or torch is unavailable.
"""

from __future__ import annotations

import numpy as np
import pytest

from _reference_bootstrap import reference_module

torch, ref_models = reference_module("simple_models")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from federated_pytorch_test_tpu.models import (  # noqa: E402
    ContextgenCNN,
    EncoderCNN,
    Net,
    Net1,
    Net2,
    PredictorCNN,
    ResNet9,
    ResNet18,
)
from federated_pytorch_test_tpu.utils import blocks as blocklib  # noqa: E402
from federated_pytorch_test_tpu.utils import codec  # noqa: E402


def _torch_flat(tnet, first_fc=None, chw=None) -> np.ndarray:
    """Flatten torch params in enumeration order, each leaf transformed
    to our layout first (so the segment ravels match our leaves)."""
    segs = []
    for name, p in tnet.named_parameters():
        w = p.detach().numpy().astype(np.float32)
        if w.ndim == 4:                       # conv OIHW -> HWIO
            w = np.transpose(w, (2, 3, 1, 0))
        elif w.ndim == 2:                     # linear [out, in] -> [in, out]
            if name == first_fc:
                C, H, W = chw                 # flatten-permutation boundary
                w = (w.reshape(w.shape[0], C, H, W)
                     .transpose(2, 3, 1, 0)
                     .reshape(H * W * C, w.shape[0]))
            else:
                w = w.T
        segs.append(w.ravel())
    return np.concatenate(segs)


def _load_into_ours(model, params, flat: np.ndarray):
    order = model.param_order()
    mask = blocklib.build_mask(
        jax.tree.map(lambda _: 0, params),
        blocklib.block_paths(order, [0, len(order) - 1]))
    assert codec.masked_size(params, order, mask) == flat.size, (
        "parameter count mismatch vs the reference enumeration")
    return codec.put_trainable_values(params, order, mask,
                                      jnp.asarray(flat))


def _check(tnet, model, x_nchw, first_fc=None, chw=None, atol=1e-4,
           apply_kwargs=None, out_nchw=False):
    tnet.eval()
    with torch.no_grad():
        want = tnet(torch.tensor(x_nchw)).numpy()
    if out_nchw:                    # conv-shaped torch output -> NHWC
        want = np.transpose(want, (0, 2, 3, 1))
    x = jnp.asarray(np.transpose(x_nchw, (0, 2, 3, 1)))
    params, batch_stats = model.init_variables(jax.random.PRNGKey(0), x,
                                               **(apply_kwargs or {}))
    params = _load_into_ours(model, params, _torch_flat(tnet, first_fc, chw))
    variables = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
    got = model.apply(variables, x, **(apply_kwargs or {}))
    np.testing.assert_allclose(np.asarray(got), want, rtol=0, atol=atol)


def _x(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


@pytest.mark.parametrize("tcls,ours,first_fc,chw", [
    (ref_models.Net, Net, "fc1.weight", (16, 5, 5)),
    (ref_models.Net1, Net1, "fc1.weight", (64, 5, 5)),
    (ref_models.Net2, Net2, "fc1.weight", (512, 2, 2)),
])
def test_classifier_forward_matches_reference(tcls, ours, first_fc, chw):
    torch.manual_seed(11)
    _check(tcls(), ours(), _x((4, 3, 32, 32)), first_fc=first_fc, chw=chw,
           apply_kwargs={"train": False})


@pytest.mark.parametrize("tfac,ours", [
    (ref_models.ResNet9, ResNet9),
    (ref_models.ResNet18, ResNet18),
])
def test_resnet_forward_matches_reference(tfac, ours):
    # after avg_pool the flat axis is channels-only: no fc permutation
    torch.manual_seed(13)
    _check(tfac(), ours(), _x((4, 3, 32, 32)), atol=2e-4,
           apply_kwargs={"train": False})


def test_cpc_encoder_matches_reference():
    """Also pins TapConv (im2col stem) against torch's TRUE dilated
    convolutions, independently of lax.conv_general_dilated."""
    torch.manual_seed(17)
    _check(ref_models.EncoderCNN(latent_dim=64), EncoderCNN(latent_dim=64),
           _x((4, 8, 32, 32)), atol=1e-4)


def test_cpc_contextgen_matches_reference():
    torch.manual_seed(19)
    _check(ref_models.ContextgenCNN(latent_dim=32),
           ContextgenCNN(latent_dim=32), _x((2, 32, 3, 3)), atol=1e-5,
           out_nchw=True)


def test_cpc_predictor_matches_reference():
    torch.manual_seed(23)
    tnet = ref_models.PredictorCNN(latent_dim=32, reduced_dim=8)
    model = PredictorCNN(latent_dim=32, reduced_dim=8)
    lat_nchw = _x((2, 32, 3, 3))
    ctx_nchw = _x((2, 32, 3, 3), seed=1)
    tnet.eval()
    with torch.no_grad():
        want_rl, want_pred = tnet(torch.tensor(lat_nchw),
                                  torch.tensor(ctx_nchw))
    lat = jnp.asarray(np.transpose(lat_nchw, (0, 2, 3, 1)))
    ctx = jnp.asarray(np.transpose(ctx_nchw, (0, 2, 3, 1)))
    params, _ = model.init_variables(jax.random.PRNGKey(0), lat, ctx)
    params = _load_into_ours(model, params, _torch_flat(tnet))
    got_rl, got_pred = model.apply({"params": params}, lat, ctx)
    for got, want in ((got_rl, want_rl), (got_pred, want_pred)):
        np.testing.assert_allclose(
            np.asarray(got), np.transpose(want.numpy(), (0, 2, 3, 1)),
            rtol=0, atol=1e-5)
