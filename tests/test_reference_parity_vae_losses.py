"""VAE / clustering-VAE loss cross-check vs the reference's ACTUAL code.

Both loss definitions live inside training scripts whose module bodies
cannot be imported (they launch runs), so — like the InfoNCE check —
the function defs are AST-extracted read-only and executed with their
free names supplied (``torch``, ``math``, ``reconstruction_function``,
the ``Kc`` module global).  Our vectorised losses (train/vae_losses.py)
must match the reference's Python-loop versions on random inputs:
the plain ELBO (federated_vae.py:96-108) and all four clustering cost
terms + the combined loss (federated_vae_cl.py:101-162).

Skipped when /root/reference or torch is unavailable.
"""

from __future__ import annotations

import ast
import math
import os

import jax.numpy as jnp
import numpy as np
import pytest

from _reference_bootstrap import REF_SRC, reference_module

torch, _ = reference_module("simple_models")   # torch + skip handling

from federated_pytorch_test_tpu.train import vae_losses  # noqa: E402


def _extract(script, names, ns):
    """Function defs ``names`` from ``script``, exec'd into ``ns``."""
    path = os.path.join(REF_SRC, script)
    if not os.path.exists(path):
        pytest.skip(f"reference {script} not available")
    with open(path) as f:
        tree = ast.parse(f.read())
    fns = [n for n in tree.body
           if isinstance(n, ast.FunctionDef) and n.name in names]
    assert {f.name for f in fns} == set(names)
    exec(compile(ast.Module(body=fns, type_ignores=[]),  # noqa: S102
                 path, "exec"), ns)
    return ns


def test_vae_loss_matches_reference():
    ns = _extract(
        "federated_vae.py", ["loss_function"],
        {"torch": torch,
         "reconstruction_function": torch.nn.MSELoss(reduction="sum")})
    rng = np.random.default_rng(3)
    x = rng.normal(size=(5, 3, 8, 8)).astype(np.float32)
    recon = rng.normal(size=(5, 3, 8, 8)).astype(np.float32)
    mu = rng.normal(size=(5, 10)).astype(np.float32)
    logvar = rng.normal(size=(5, 10)).astype(np.float32)
    with torch.no_grad():
        want = float(ns["loss_function"](
            torch.tensor(recon), torch.tensor(x), torch.tensor(mu),
            torch.tensor(logvar)))
    got = float(vae_losses.vae_loss(jnp.asarray(recon), jnp.asarray(x),
                                    jnp.asarray(mu), jnp.asarray(logvar)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_vae_cl_losses_match_reference():
    Kc, L, B = 4, 6, 5
    ns = _extract(
        "federated_vae_cl.py",
        ["cost1", "cost2", "cost21", "cost3", "loss_function"],
        {"torch": torch, "math": math, "Kc": Kc})
    rng = np.random.default_rng(11)

    def pos(*shape):          # strictly positive (variances, softmax probs)
        return (rng.uniform(0.1, 2.0, size=shape)).astype(np.float32)

    x = rng.normal(size=(B, 3, 8, 8)).astype(np.float32)
    ekhat = rng.dirichlet(np.ones(Kc), size=B).astype(np.float32)
    mu_xi = {k: rng.normal(size=(B, L)).astype(np.float32)
             for k in range(Kc)}
    sig2_xi = {k: pos(B, L) for k in range(Kc)}
    mu_b = {k: rng.normal(size=(B, L)).astype(np.float32)
            for k in range(Kc)}
    sig2_b = {k: pos(B, L) for k in range(Kc)}
    mu_th = {k: rng.normal(size=(B, 3, 8, 8)).astype(np.float32)
             for k in range(Kc)}
    sig2_th = {k: pos(B, 3, 8, 8) for k in range(Kc)}

    t = torch.tensor
    with torch.no_grad():
        # the reference's in-place ops (err.pow_ etc.) mutate their args,
        # so hand each call fresh tensors
        want_c1 = float(ns["cost1"](t(ekhat[:, 0]), t(mu_th[0]),
                                    t(sig2_th[0]), t(x)))
        want_c2 = float(ns["cost2"](t(ekhat[:, 0])))
        want_c21 = float(ns["cost21"](t(ekhat[:, 0])))
        want_c3 = float(ns["cost3"](t(ekhat[:, 0]), t(mu_xi[0]),
                                    t(sig2_xi[0]), t(mu_b[0]),
                                    t(sig2_b[0])))
        want_total = float(ns["loss_function"](
            t(ekhat), {k: t(v) for k, v in mu_xi.items()},
            {k: t(v) for k, v in sig2_xi.items()},
            {k: t(v) for k, v in mu_b.items()},
            {k: t(v) for k, v in sig2_b.items()},
            {k: t(v) for k, v in mu_th.items()},
            {k: t(v) for k, v in sig2_th.items()}, t(x)))

    j = jnp.asarray
    xj = j(np.transpose(x, (0, 2, 3, 1)))            # ours is NHWC
    th_j = lambda d: j(np.stack([np.transpose(d[k], (0, 2, 3, 1))
                                 for k in range(Kc)]))
    stack = lambda d: j(np.stack([d[k] for k in range(Kc)]))

    np.testing.assert_allclose(
        float(vae_losses.cost1(j(ekhat[:, 0]), th_j(mu_th)[0],
                               th_j(sig2_th)[0], xj)), want_c1, rtol=1e-5)
    np.testing.assert_allclose(
        float(vae_losses.cost2(j(ekhat[:, 0]))), want_c2, rtol=1e-5)
    np.testing.assert_allclose(
        float(vae_losses.cost21(j(ekhat[:, 0]))), want_c21, rtol=1e-5)
    np.testing.assert_allclose(
        float(vae_losses.cost3(j(ekhat[:, 0]), j(mu_xi[0]), j(sig2_xi[0]),
                               j(mu_b[0]), j(sig2_b[0]))),
        want_c3, rtol=1e-5)
    got_total = float(vae_losses.vae_cl_loss(
        j(ekhat), stack(mu_xi), stack(sig2_xi), stack(mu_b), stack(sig2_b),
        th_j(mu_th), th_j(sig2_th), xj))
    np.testing.assert_allclose(got_total, want_total, rtol=1e-5)
