"""Mid-run checkpoint/resume tests (SURVEY.md section 5).

The contract: kill a run after any communication round, resume from the
checkpoint, and the continued history/params must match an uninterrupted
run exactly (same staging PRNG, same optimizer state, same ADMM state).
"""

import numpy as np
import pytest

from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
from federated_pytorch_test_tpu.models.simple import Net
from federated_pytorch_test_tpu.train import (
    AdmmConsensus,
    BlockwiseFederatedTrainer,
    FederatedConfig,
)

K = 4


class Killed(Exception):
    pass


def small_cfg(**kw):
    base = dict(K=K, Nloop=1, Nepoch=1, Nadmm=3, default_batch=8,
                check_results=False, admm_rho0=0.1, seed=5)
    base.update(kw)
    return FederatedConfig(**base)


@pytest.fixture(scope="module")
def data():
    return FederatedCifar10(K=K, batch=8, limit_per_client=16, limit_test=8)


def run_trainer(cfg, data, L=1, **run_kw):
    t = BlockwiseFederatedTrainer(Net(), cfg, data, AdmmConsensus())
    t.L = L
    run_kw.setdefault("log", lambda m: None)
    return t.run(**run_kw)


def strip(rec):
    # wall-clock and compile/cache-attribution fields legitimately
    # differ between runs: a resumed process re-compiles at its first
    # continued round, so cache_hit lands on rounds the uninterrupted
    # run compiled nothing in (obs/costs.py)
    return {k: v for k, v in rec.items()
            if isinstance(v, (int, float)) and not k.endswith("_seconds")
            and k not in ("cache_hit", "peak_device_bytes")}


class TestMidrunResume:
    # both checkpoint write paths honor the kill/resume contract: the
    # async writer's abort-path drain makes the last submitted round
    # durable before the trainer dies, exactly like the sync save
    @pytest.mark.parametrize("async_ckpt", [False, True],
                             ids=["sync", "async"])
    def test_killed_run_resumes_to_identical_history(self, data, tmp_path,
                                                     async_ckpt):
        cfg = small_cfg(async_checkpoint=async_ckpt)
        ck = str(tmp_path / "ck")

        _, hist_full = run_trainer(cfg, data)

        def bomb(state, rec):
            if rec["nadmm"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(cfg, data, checkpoint_path=ck, on_round=bomb)

        state_r, hist_r = run_trainer(cfg, data, checkpoint_path=ck,
                                      resume=True)
        assert len(hist_r) == len(hist_full)
        # restored prefix + continued rounds must match the uninterrupted
        # run: same shuffle PRNG state, optimizer state, and z/y/rho
        for a, b in zip(hist_r, hist_full):
            sa, sb = strip(a), strip(b)
            assert sa.keys() == sb.keys()
            for k in sa:
                np.testing.assert_allclose(sa[k], sb[k], rtol=1e-5,
                                           err_msg=f"history field {k}")

    def test_params_match_uninterrupted(self, data, tmp_path):
        cfg = small_cfg(Nadmm=2)
        ck = str(tmp_path / "ck")
        state_full, _ = run_trainer(cfg, data)

        def bomb(state, rec):
            if rec["nadmm"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(cfg, data, checkpoint_path=ck, on_round=bomb)
        state_r, _ = run_trainer(cfg, data, checkpoint_path=ck, resume=True)

        ref = jax_to_np(state_full.params)
        res = jax_to_np(state_r.params)
        for (pa, a), (pb, b) in zip(ref, res):
            assert pa == pb
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7,
                                       err_msg=str(pa))

    def test_block_boundary_resume(self, data, tmp_path):
        # kill exactly at a block rollover: the checkpoint then carries no
        # block vars (fresh-init path on resume) — both blocks must run
        cfg = small_cfg(Nadmm=1)
        ck = str(tmp_path / "ck")
        _, hist_full = run_trainer(cfg, data, L=2)

        seen = []

        def bomb(state, rec):
            seen.append(rec["block"])
            if rec["block"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(cfg, data, L=2, checkpoint_path=ck, on_round=bomb)
        _, hist_r = run_trainer(cfg, data, L=2, checkpoint_path=ck,
                                resume=True)
        assert [h["block"] for h in hist_r] == [h["block"] for h in hist_full]
        for a, b in zip(hist_r, hist_full):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)

    @pytest.mark.parametrize("comp_kw", [
        pytest.param(dict(compress="q8"), id="q8"),
        pytest.param(dict(compress="topk", topk_frac=0.1,
                          error_feedback=True), id="topk_ef"),
        pytest.param(dict(compress="q8", fused_collective=True),
                     marks=pytest.mark.fusedcomm, id="q8_fused"),
        pytest.param(dict(compress="q8", overlap_staging=True),
                     marks=pytest.mark.fusedcomm, id="q8_overlap"),
    ])
    def test_compressed_state_resumes_identically(self, data, tmp_path,
                                                  comp_kw):
        # the per-client compressor state (PRNG key / EF residual) rides
        # in the midrun checkpoint: a resumed compressed run must replay
        # the uninterrupted trajectory exactly — including through the
        # packed-collective comm path and the prestage-overlap cache
        # (both are keyed on round counters, so resume re-derives them)
        cfg = small_cfg(**comp_kw)
        ck = str(tmp_path / "ck")
        _, hist_full = run_trainer(cfg, data)

        def bomb(state, rec):
            if rec["nadmm"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(cfg, data, checkpoint_path=ck, on_round=bomb)
        state_r, hist_r = run_trainer(cfg, data, checkpoint_path=ck,
                                      resume=True)
        assert state_r.comp is not None
        assert len(hist_r) == len(hist_full)
        for a, b in zip(hist_r, hist_full):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
            assert a["bytes_on_wire"] == b["bytes_on_wire"]

    def test_pre_compression_checkpoint_resumes_with_fresh_comp(
            self, data, tmp_path):
        # a checkpoint written by a DENSE run carries no comp_state_leaves;
        # resuming it under a compressed config must fall back to fresh
        # per-client state instead of failing (engine _restore_midrun)
        ck = str(tmp_path / "ck")

        def bomb(state, rec):
            if rec["nadmm"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(small_cfg(), data, checkpoint_path=ck, on_round=bomb)
        state_r, hist_r = run_trainer(small_cfg(compress="q8"), data,
                                      checkpoint_path=ck, resume=True)
        assert state_r.comp is not None
        assert len(hist_r) == 3                 # Nadmm=3 rounds completed
        # the continued rounds report the compressed wire size
        comp_bytes = hist_r[-1]["bytes_on_wire"]
        assert 0 < comp_bytes < K * 4 * hist_r[-1]["N"]

    def test_completed_run_resume_is_noop(self, data, tmp_path):
        cfg = small_cfg(Nadmm=1)
        ck = str(tmp_path / "ck")
        _, hist = run_trainer(cfg, data, checkpoint_path=ck)
        state2, hist2 = run_trainer(cfg, data, checkpoint_path=ck,
                                    resume=True)
        # nothing left to do: restored history returned unchanged
        assert len(hist2) == len(hist)


def jax_to_np(tree):
    import jax

    # jax.tree_util spelling: jax.tree.flatten_with_path only exists in
    # newer jax releases than the pinned 0.4.x
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(p), np.asarray(v)) for p, v in flat]


class TestSlotSwapCrashWindows:
    """Slot-level crash-window coverage for save_checkpoint_swapped.

    The window: a kill AFTER save to ``path.next`` finalized but BEFORE the
    swap renamed it into ``path`` leaves the NEWER checkpoint in ``.next``
    and the round-stale one in ``path``; the probe must prefer ``.next``
    (when present it is always the newest by protocol) and the next swap
    must not rmtree it.
    """

    @staticmethod
    def _save(path, round_):
        from federated_pytorch_test_tpu.utils.checkpoint import (
            save_checkpoint,
        )

        save_checkpoint(path, {"x": np.float32(round_)},
                        {"round": round_})

    @staticmethod
    def _round_of(path):
        from federated_pytorch_test_tpu.utils.checkpoint import (
            load_checkpoint,
        )

        return load_checkpoint(path)[1]["round"]

    def test_newest_slot_prefers_next(self, tmp_path):
        from federated_pytorch_test_tpu.utils.checkpoint import newest_slot

        ck = str(tmp_path / "ck")
        self._save(ck, 1)                # stale primary (round 1)
        self._save(ck + ".next", 2)      # crash-stranded newer save
        assert newest_slot(ck) == ck + ".next"
        assert self._round_of(newest_slot(ck)) == 2

    def test_swap_after_crash_keeps_newer(self, tmp_path):
        from federated_pytorch_test_tpu.utils.checkpoint import (
            newest_slot,
            save_checkpoint_swapped,
        )

        ck = str(tmp_path / "ck")
        self._save(ck, 1)
        self._save(ck + ".next", 2)
        # the resumed run restores round 2 and checkpoints round 3: the
        # swap must promote .next (round 2) over the stale primary, never
        # leaving the newest data in a slot its own rmtree then deletes
        save_checkpoint_swapped(ck, {"x": np.float32(3)}, {"round": 3})
        assert newest_slot(ck) == ck
        assert self._round_of(ck) == 3

    def test_checksum_sidecar_written_and_verifies(self, tmp_path):
        from federated_pytorch_test_tpu.utils.checkpoint import (
            CHECKSUM_FILE,
            verify_checkpoint,
        )

        ck = str(tmp_path / "ck")
        self._save(ck, 1)
        assert (tmp_path / "ck" / CHECKSUM_FILE).exists()
        assert verify_checkpoint(ck) is True

    def test_tampered_checkpoint_fails_verification(self, tmp_path):
        import os

        from federated_pytorch_test_tpu.utils.checkpoint import (
            CHECKSUM_FILE,
            CheckpointCorruptError,
            verify_checkpoint,
        )

        ck = str(tmp_path / "ck")
        self._save(ck, 1)
        victim = next(
            os.path.join(r, f) for r, _, fs in os.walk(ck)
            for f in fs if f != CHECKSUM_FILE)
        with open(victim, "r+b") as fh:      # flip one byte in place
            b = fh.read(1)
            fh.seek(0)
            fh.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(CheckpointCorruptError):
            verify_checkpoint(ck)

    def test_swap_sweeps_stranded_orbax_tmp_dirs(self, tmp_path):
        import os
        import time

        from federated_pytorch_test_tpu.utils.checkpoint import (
            save_checkpoint_swapped,
        )

        ck = str(tmp_path / "ck")
        stranded = tmp_path / "ck.next.orbax-checkpoint-tmp-12345"
        stranded.mkdir()
        (stranded / "partial").write_bytes(b"x")
        fresh = tmp_path / "ck.next.orbax-checkpoint-tmp-67890"
        fresh.mkdir()
        # stranded = provably stale (a crashed earlier run); fresh = could
        # be a skewed peer's in-flight save on a shared fs — must survive
        old = time.time() - 7200
        os.utime(stranded, (old, old))
        save_checkpoint_swapped(ck, {"x": np.float32(1)}, {"round": 1})
        assert not stranded.exists()
        assert fresh.exists()
        assert self._round_of(ck) == 1


class TestCorruptSlotFallback:
    """Atomic-checkpoint satellite: a bit-rotted or truncated slot must not
    kill the resume — the engine walks newest-to-oldest, warns, and falls
    back; only when EVERY slot is bad does it raise CheckpointCorruptError.
    """

    @staticmethod
    def _corrupt_slot(slot):
        import os

        from federated_pytorch_test_tpu.utils.checkpoint import (
            CHECKSUM_FILE,
        )

        victim = next(
            os.path.join(r, f) for r, _, fs in os.walk(slot)
            for f in fs if f != CHECKSUM_FILE)
        with open(victim, "r+b") as fh:
            b = fh.read(1)
            fh.seek(0)
            fh.write(bytes([b[0] ^ 0xFF]))

    def _bombed_run_with_slots(self, data, ck, **cfg_kw):
        """Kill after round 1 so BOTH ck (round 1) and ck.old (round 0)
        checkpoint slots exist when the resume probes them."""
        def bomb(state, rec):
            if rec["nadmm"] == 1:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(small_cfg(**cfg_kw), data, checkpoint_path=ck,
                        on_round=bomb)

    # the async writer must preserve the slot protocol (rotation order,
    # sha256 sidecars) byte-for-byte — the corrupt-slot walk is the proof
    @pytest.mark.parametrize("async_ckpt", [False, True],
                             ids=["sync", "async"])
    def test_corrupt_primary_falls_back_to_old_slot(self, data, tmp_path,
                                                    async_ckpt):
        import os

        ck = str(tmp_path / "ck")
        _, hist_full = run_trainer(small_cfg(), data)
        self._bombed_run_with_slots(data, ck, async_checkpoint=async_ckpt)
        assert os.path.isdir(ck + ".old")
        self._corrupt_slot(ck)

        msgs = []
        _, hist_r = run_trainer(small_cfg(), data, checkpoint_path=ck,
                                resume=True, log=msgs.append)
        assert any("unusable" in m and "falling back" in m for m in msgs)
        # the stale slot is one round behind: the resumed run replays that
        # round and must still land on the uninterrupted history exactly
        assert len(hist_r) == len(hist_full)
        for a, b in zip(hist_r, hist_full):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
            np.testing.assert_allclose(a["dual_residual"],
                                       b["dual_residual"], rtol=1e-5)

    def test_all_slots_corrupt_raises(self, data, tmp_path):
        from federated_pytorch_test_tpu.utils.checkpoint import (
            CheckpointCorruptError,
            checkpoint_slots,
        )

        ck = str(tmp_path / "ck")
        self._bombed_run_with_slots(data, ck)
        slots = checkpoint_slots(ck)
        assert len(slots) >= 2
        for slot in slots:
            self._corrupt_slot(slot)
        with pytest.raises(CheckpointCorruptError, match="no valid"):
            run_trainer(small_cfg(), data, checkpoint_path=ck,
                        resume=True, log=lambda m: None)


class TestAsyncRunResume:
    """--async-rounds kill/resume (ISSUE 6): the staleness ledger
    (arrival round, birth round, cumulative rejections) rides in the
    checkpoint meta and the frozen per-client params ARE the in-flight
    buffer, so a resumed async run must replay the uninterrupted
    trajectory exactly — through both checkpoint writers."""

    ASYNC_CFG = dict(Nadmm=4, async_rounds=True, max_staleness=2,
                     fault_spec="delay=0.5,delay_max=2,seed=9")
    LEDGER_FIELDS = ("async_arrived", "admission_rejected", "buffer_depth",
                     "n_active")

    @pytest.mark.asyncfl
    @pytest.mark.parametrize("async_ckpt", [False, True],
                             ids=["sync", "async"])
    def test_async_run_resumes_identically(self, data, tmp_path,
                                           async_ckpt):
        cfg = small_cfg(async_checkpoint=async_ckpt, **self.ASYNC_CFG)
        ck = str(tmp_path / "ck")
        _, hist_full = run_trainer(cfg, data)
        # the kill point must leave updates in flight, or the ledger
        # restore proves nothing
        assert hist_full[1]["buffer_depth"] > 0

        def bomb(state, rec):
            if rec["nadmm"] == 1:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(cfg, data, checkpoint_path=ck, on_round=bomb)
        _, hist_r = run_trainer(cfg, data, checkpoint_path=ck, resume=True)
        assert len(hist_r) == len(hist_full)
        for a, b in zip(hist_r, hist_full):
            sa, sb = strip(a), strip(b)
            assert sa.keys() == sb.keys()
            # the ledger-derived counters are bit-identical by contract
            for k in self.LEDGER_FIELDS:
                assert sa[k] == sb[k], k
            assert a["staleness_hist"] == b["staleness_hist"]
            for k in sa:
                np.testing.assert_allclose(sa[k], sb[k], rtol=1e-5,
                                           err_msg=f"history field {k}")

    @pytest.mark.asyncfl
    def test_async_block_boundary_resume(self, data, tmp_path):
        # a block rollover voids the in-flight buffer (block variables
        # change identity); a kill exactly there must resume onto the
        # fresh-ledger path and still match the uninterrupted run
        cfg = small_cfg(Nadmm=2, async_rounds=True, max_staleness=2,
                        fault_spec="delay=0.5,delay_max=2,seed=9")
        ck = str(tmp_path / "ck")
        _, hist_full = run_trainer(cfg, data, L=2)

        def bomb(state, rec):
            if rec["block"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(cfg, data, L=2, checkpoint_path=ck, on_round=bomb)
        _, hist_r = run_trainer(cfg, data, L=2, checkpoint_path=ck,
                                resume=True)
        assert [h["block"] for h in hist_r] == \
            [h["block"] for h in hist_full]
        for a, b in zip(hist_r, hist_full):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
            assert a["buffer_depth"] == b["buffer_depth"]


class TestElasticResume:
    """Mesh-reshaping resume (elastic-federation tentpole): a checkpoint
    written on a D-device mesh must restore onto a D'-device mesh when
    ``elastic_resume`` is set — the K client rows restage onto whatever
    mesh the resuming process built (PARITY.md: bitwise when D' == D,
    allclose trajectory + exact history shape when D' != D) — and must
    fail with the typed ``CheckpointGeometryError`` when it is not."""

    @pytest.fixture(scope="class")
    def data8(self):
        return FederatedCifar10(K=8, batch=8, limit_per_client=16,
                                limit_test=8)

    @staticmethod
    def e_cfg(d, **kw):
        base = dict(K=8, Nloop=1, Nepoch=1, Nadmm=3, default_batch=8,
                    check_results=False, admm_rho0=0.1, seed=5,
                    num_devices=d)
        base.update(kw)
        return FederatedConfig(**base)

    @pytest.mark.parametrize("d_from,d_to", [
        pytest.param(8, 8, id="8to8"),
        pytest.param(8, 4, id="8to4"),
        pytest.param(4, 8, id="4to8"),
    ])
    def test_reshape_resume_matches_uninterrupted(self, data8, tmp_path,
                                                  d_from, d_to):
        ck = str(tmp_path / "ck")
        _, hist_full = run_trainer(self.e_cfg(d_from), data8)

        def bomb(state, rec):
            if rec["nadmm"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(self.e_cfg(d_from), data8, checkpoint_path=ck,
                        on_round=bomb)
        _, hist_r = run_trainer(self.e_cfg(d_to, elastic_resume=True),
                                data8, checkpoint_path=ck, resume=True)
        # the XLA cost-model attributions describe the PER-DEVICE program,
        # whose shard shapes change with the mesh — they are not part of
        # the trajectory contract across a reshape
        mesh_scaled = ("flops_round", "hlo_bytes_accessed")
        assert len(hist_r) == len(hist_full)
        for a, b in zip(hist_r, hist_full):
            sa, sb = strip(a), strip(b)
            assert sa.keys() == sb.keys()
            for k in sa:
                if d_from == d_to:
                    # same geometry: the elastic flag must not perturb
                    # the bitwise kill/resume contract
                    np.testing.assert_array_equal(
                        sa[k], sb[k], err_msg=f"history field {k}")
                elif k not in mesh_scaled:
                    # reshaped mesh: cross-device reduction order moves,
                    # so the contract is allclose, not bitwise
                    np.testing.assert_allclose(
                        sa[k], sb[k], rtol=1e-4, atol=1e-6,
                        err_msg=f"history field {k}")

    def test_geometry_mismatch_without_flag_raises(self, data8, tmp_path):
        from federated_pytorch_test_tpu.utils.checkpoint import (
            CheckpointGeometryError,
        )

        ck = str(tmp_path / "ck")

        def bomb(state, rec):
            if rec["nadmm"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(self.e_cfg(8), data8, checkpoint_path=ck,
                        on_round=bomb)
        with pytest.raises(CheckpointGeometryError, match="elastic"):
            run_trainer(self.e_cfg(4), data8, checkpoint_path=ck,
                        resume=True)
        # the error is actionable, not fatal to the data: the same resume
        # succeeds once the operator opts in
        _, hist_r = run_trainer(self.e_cfg(4, elastic_resume=True), data8,
                                checkpoint_path=ck, resume=True)
        assert len(hist_r) == 3

    def test_k_change_rejected_even_with_flag(self, data, tmp_path):
        # K is the federation's identity — elastic_resume covers mesh
        # geometry only, never the client axis
        from federated_pytorch_test_tpu.utils.checkpoint import (
            CheckpointGeometryError,
            load_checkpoint,
            validate_geometry,
        )

        ck = str(tmp_path / "ck")

        def bomb(state, rec):
            if rec["nadmm"] == 0:
                raise Killed

        with pytest.raises(Killed):
            run_trainer(small_cfg(), data, checkpoint_path=ck,
                        on_round=bomb)
        _, meta = load_checkpoint(ck)
        with pytest.raises(CheckpointGeometryError, match="K"):
            validate_geometry(meta, devices=8, processes=1, K=8,
                              elastic=True)


class TestChurnResume:
    """Client churn (join=/leave= fault family): the membership ledger is
    a pure function of (seed, round coords), so the same seed must yield
    the same ledger on a fresh run AND across a mid-run kill/resume —
    the live roster rides in the checkpoint meta."""

    CHURN_CFG = dict(Nadmm=4, fault_spec="join=0.4,leave=0.4,seed=11")
    LEDGER_FIELDS = ("members_active", "joined", "left")

    def test_same_seed_same_ledger(self, data):
        cfg = small_cfg(**self.CHURN_CFG)
        _, h1 = run_trainer(cfg, data)
        _, h2 = run_trainer(cfg, data)
        ledger = [tuple(h[k] for k in self.LEDGER_FIELDS) for h in h1]
        assert ledger == \
            [tuple(h[k] for k in self.LEDGER_FIELDS) for h in h2]
        # the schedule must actually churn for this suite to mean
        # anything (seed=11: roster dips to 2 of 4 members)
        assert sum(h["joined"] + h["left"] for h in h1) > 0
        assert min(h["members_active"] for h in h1) < K

    def test_churned_run_resumes_identically(self, data, tmp_path):
        cfg = small_cfg(**self.CHURN_CFG)
        ck = str(tmp_path / "ck")
        _, hist_full = run_trainer(cfg, data)

        def bomb(state, rec):
            if rec["nadmm"] == 1:    # mid-churn: the roster must survive
                raise Killed

        with pytest.raises(Killed):
            run_trainer(cfg, data, checkpoint_path=ck, on_round=bomb)
        _, hist_r = run_trainer(cfg, data, checkpoint_path=ck, resume=True)
        assert len(hist_r) == len(hist_full)
        for a, b in zip(hist_r, hist_full):
            sa, sb = strip(a), strip(b)
            assert sa.keys() == sb.keys()
            # the ledger is bit-identical by contract
            for k in self.LEDGER_FIELDS:
                assert sa[k] == sb[k], k
            for k in sa:
                np.testing.assert_allclose(sa[k], sb[k], rtol=1e-5,
                                           err_msg=f"history field {k}")

    def test_churn_off_records_carry_no_membership_fields(self, data):
        # bit-identity satellite: a static-roster run's records must stay
        # byte-identical to schema v8 — the membership fields may only
        # appear when join=/leave= is configured
        _, hist = run_trainer(small_cfg(), data)
        for h in hist:
            assert not any(k in h for k in self.LEDGER_FIELDS)


class TestFaultyRunResume:
    """Fault schedule + guard/quarantine state across a kill/resume: the
    continued run must replay the interrupted trajectory bit-for-bit —
    the fault draws are stateless in the round coordinates and the
    quarantine ledger + guard scale ride in the checkpoint meta."""

    FAULT_CFG = dict(
        Nadmm=4,
        fault_spec="drop=0.3,corrupt=0.5,mode=nan,seed=7",
        update_guard=True, quarantine_rounds=1,
    )

    def test_faulty_guarded_run_resumes_identically(self, data, tmp_path):
        cfg = small_cfg(**self.FAULT_CFG)
        ck = str(tmp_path / "ck")
        _, hist_full = run_trainer(cfg, data)
        # the schedule must actually exercise faults + the guard for this
        # test to mean anything
        assert sum(h["fault_corrupted"] for h in hist_full) > 0
        assert sum(h["guard_trips"] for h in hist_full) > 0
        assert sum(h["quarantined"] for h in hist_full) > 0

        def bomb(state, rec):
            if rec["nadmm"] == 1:    # mid-quarantine: ledger must survive
                raise Killed

        with pytest.raises(Killed):
            run_trainer(cfg, data, checkpoint_path=ck, on_round=bomb)
        _, hist_r = run_trainer(cfg, data, checkpoint_path=ck, resume=True)
        assert len(hist_r) == len(hist_full)
        for a, b in zip(hist_r, hist_full):
            sa, sb = strip(a), strip(b)
            assert sa.keys() == sb.keys()
            for k in sa:
                np.testing.assert_allclose(sa[k], sb[k], rtol=1e-5,
                                           err_msg=f"history field {k}")
