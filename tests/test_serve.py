"""Serving plane (serve/ + engine/obs/control wiring, PR 18 tentpole).

The determinism contract under test (PARITY.md v0.14):

- the serve schedule is a pure function of (serve seed, spec, round
  index): request counts, batch plans, padding, the swap sequence and
  the drift flags re-derive bit-exactly from the stream header, across
  parses and across a kill/resume;
- a request in flight during a hot-swap is answered by exactly the old
  or exactly the new weights, never a mixture (the double buffer
  publishes with one atomic reference assignment);
- serving is a read: a run with the serving plane on trains bitwise
  the same trajectory as the same config with serving off, and
  ``serve_spec="none"`` is the literal seed path (no serve records, no
  plane constructed);
- the served eval stream closes the loop: seeded label drift trips the
  watchdog's ``serve_drift`` rule and, in act mode, a recorded policy
  intervention that forces a serving refresh at the next boundary.
"""

import dataclasses
import threading

import numpy as np
import pytest

import jax
import flax.linen as nn

from federated_pytorch_test_tpu.control.replay import replay
from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
from federated_pytorch_test_tpu.models.base import (
    BlockModule,
    elu,
    flatten,
    max_pool_2x2,
    pairs,
)
from federated_pytorch_test_tpu.obs.report import read_records, summarize
from federated_pytorch_test_tpu.serve import (
    SERVE_FIELDS,
    BatchedPredictor,
    DoubleBuffer,
    EvalStream,
    MicroBatcher,
    ServeSchedule,
    bucket_for,
    pad_to_bucket,
    version_for,
)
from federated_pytorch_test_tpu.train import (
    AdmmConsensus,
    BlockwiseFederatedTrainer,
    FederatedConfig,
)

pytestmark = pytest.mark.serve

K = 4

#: 8 rounds: hot-swap every 2, total label shift injected from round 4
SPEC = "qps=12,round_minutes=0.5,buckets=4+16+64,swap_every=2,drift_at=4,seed=3"


class TinyNet(BlockModule):
    """2-block toy CNN (test_engine.py convention)."""

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = max_pool_2x2(elu(nn.Conv(4, (5, 5), strides=(2, 2),
                                     name="conv1")(x)))
        x = flatten(x)
        return nn.Dense(10, name="fc1")(x)

    def param_order(self):
        return pairs("conv1", "fc1")

    def train_order_block_ids(self):
        return [[0, 1], [2, 3]]

    def linear_layer_ids(self):
        return [1]


class Killed(Exception):
    pass


@pytest.fixture(scope="module")
def data():
    return FederatedCifar10(K=K, batch=16, limit_per_client=32,
                            limit_test=32)


def small_cfg(**kw):
    base = dict(K=K, Nloop=2, Nepoch=1, Nadmm=4, default_batch=16,
                check_results=False, admm_rho0=0.1, seed=5,
                obs_sinks="memory")
    base.update(kw)
    return FederatedConfig(**base)


def serve_cfg(**kw):
    # health window 2 / streak 1 so the 8-round run can warm the EMA on
    # the pre-drift rounds and alert inside the drifted tail
    base = dict(serve_spec=SPEC, control="act", health_action="warn",
                health_window=2, health_streak=1, health_tput_frac=0.75)
    base.update(kw)
    return small_cfg(**base)


def run_trainer(cfg, data, **run_kw):
    t = BlockwiseFederatedTrainer(TinyNet(), cfg, data, AdmmConsensus())
    t.L = 1
    run_kw.setdefault("log", lambda m: None)
    state, hist = t.run(**run_kw)
    return t, state, hist


def param_leaves(state):
    return [np.asarray(x) for x in jax.tree.leaves(state.params)]


def det_view(rec):
    # wall-clock and compile/cache-attribution fields legitimately
    # differ between processes
    return {k: v for k, v in rec.items()
            if isinstance(v, (int, float)) and not k.endswith("_seconds")
            and k not in ("cache_hit", "peak_device_bytes")}


def pure_fields(rec):
    return {k: rec.get(k) for k in SERVE_FIELDS}


# ----------------------------------------------------------------------
# schedule purity


class TestServeSchedule:
    def test_pure_and_roundtrips(self):
        a = ServeSchedule.parse(SPEC)
        b = ServeSchedule.parse(a.spec_string())
        for r in range(16):
            assert a.record_fields(r) == b.record_fields(r)
            assert a.requests_for(r) >= 1
        assert ServeSchedule.parse("none") is None
        assert ServeSchedule.parse("") is None

    def test_swap_and_drift_sequences(self):
        s = ServeSchedule.parse(SPEC)
        assert [s.weights_version(r) for r in range(8)] == \
            [1, 1, 2, 2, 3, 3, 4, 4]
        assert [s.swap(r) for r in range(8)] == \
            [True, False] * 4
        assert [s.drift_injected(r) for r in range(8)] == \
            [False] * 4 + [True] * 4
        assert version_for(7, 2) == 4

    def test_batch_plan_accounting(self):
        s = ServeSchedule.parse(SPEC)
        plan = s.batch_plan(70)
        assert plan == [(64, 64), (16, 6)]
        assert s.padded_slots(70) == 10
        assert s.padding_waste_frac(70) == round(10 / 80, 6)
        assert bucket_for(5, (4, 16, 64)) == 16
        x = np.zeros((5, 3), np.float32)
        assert pad_to_bucket(x, 16).shape == (16, 3)

    def test_bad_specs_raise(self):
        for bad in ("qps=0", "buckets=8+4", "swap_every=0", "nope=1",
                    "drift_at=-2"):
            with pytest.raises(ValueError):
                ServeSchedule.parse(bad)


# ----------------------------------------------------------------------
# never-torn hot swap


class TestDoubleBuffer:
    def test_in_flight_requests_never_torn(self):
        # a reader mid-request sees exactly one (version, weights) pair:
        # hammer publishes from a writer while readers assert the pair
        # stays internally consistent
        buf = DoubleBuffer()
        buf.publish(1, {"w": 1.0})
        stop = threading.Event()
        torn = []

        def writer():
            v = 1
            while not stop.is_set():
                v += 1
                buf.publish(v, {"w": float(v)})

        def reader():
            for _ in range(20000):
                version, weights = buf.acquire()
                if weights["w"] != float(version):
                    torn.append((version, weights["w"]))

        w = threading.Thread(target=writer)
        readers = [threading.Thread(target=reader) for _ in range(4)]
        w.start()
        for t in readers:
            t.start()
        for t in readers:
            t.join()
        stop.set()
        w.join()
        assert not torn, torn[:5]
        assert buf.swaps >= 1

    def test_acquire_before_publish_raises(self):
        with pytest.raises(RuntimeError):
            DoubleBuffer().acquire()


# ----------------------------------------------------------------------
# batched predictor + eval stream units


class TestPredictorUnits:
    def test_pads_to_buckets_and_slices(self):
        import jax.numpy as jnp

        pred = BatchedPredictor(lambda w, x: x * w, buckets=(4, 16))
        w = jnp.float32(2.0)
        out = pred(w, np.ones((5, 2), np.float32))
        assert out.shape == (5, 2)
        np.testing.assert_allclose(out, 2.0)
        # 5 rows pad to the 16-bucket; 3 rows to the 4-bucket — the
        # compiled-shape set is bounded by the bucket list
        pred(w, np.ones((3, 2), np.float32))
        assert pred.shapes_seen <= {(4, 2), (16, 2)}

    def test_evalstream_scores_drift(self):
        sched = ServeSchedule.parse("qps=8,drift_at=2,seed=1")
        es = EvalStream(sched, window=2)
        logits = np.eye(10, dtype=np.float32)[:8]
        labels = np.arange(8) % 10
        r0 = es.score(0, logits, labels)
        r1 = es.score(1, logits, labels)
        assert r0["serve_accuracy"] == r1["serve_accuracy"] == 1.0
        assert not r0["drift_injected"]
        r2 = es.score(2, logits, labels)
        assert r2["drift_injected"]
        assert r2["serve_accuracy"] == 0.0      # total label shift
        assert r2["drift_score"] == 1.0

    def test_microbatcher_orders_and_bounds(self):
        sched = ServeSchedule.parse("qps=8,buckets=4+16,seed=1")
        mb = MicroBatcher(sched, lambda b: [row.sum() for row in b],
                          max_queue=4)
        for i in range(4):
            mb.submit(np.full((2,), i, np.float32))
        with pytest.raises(OverflowError):
            mb.submit(np.zeros((2,), np.float32))
        outs, tel = mb.drain()
        assert [float(o) for o in outs] == [0.0, 2.0, 4.0, 6.0]
        assert tel["requests"] == 4 and tel["batches"] == 1
        assert tel["padded_slots"] == 0


# ----------------------------------------------------------------------
# live integration: train -> serve -> observe -> intervene


@pytest.fixture(scope="module")
def serve_run(data, tmp_path_factory):
    tmp = tmp_path_factory.mktemp("serve")
    cfg = serve_cfg(obs_sinks="jsonl", obs_dir=str(tmp / "obs"))
    t = BlockwiseFederatedTrainer(TinyNet(), cfg, data, AdmmConsensus())
    t.L = 1
    t.obs_run_name = "serve"
    state, hist = t.run(log=lambda m: None)
    records = read_records(str(tmp / "obs" / "serve.jsonl"),
                           validate=True)
    return cfg, state, hist, records


class TestServeIntegration:
    def test_records_rederive_and_replay(self, serve_run):
        cfg, _, hist, records = serve_run
        serves = [r for r in records if r.get("event") == "serve"]
        assert len(serves) == len(hist) == 8
        sched = ServeSchedule.parse(cfg.serve_spec)
        for rec, (r, fields) in zip(
                serves, sched.expected_records(range(8))):
            assert rec["round_index"] == r
            assert pure_fields(rec) == fields
        errors, stats = replay(records)
        assert not errors, errors
        assert stats["serve_records"] == 8, stats

    def test_swap_telemetry(self, serve_run):
        _, _, _, records = serve_run
        serves = [r for r in records if r.get("event") == "serve"]
        for rec in serves:
            if rec["swap"]:
                assert rec.get("swap_gap_seconds", 0) >= 0
            assert rec["serve_qps"] > 0
            assert rec["serve_p99_ms"] >= rec["serve_p50_ms"]
        s = summarize(records)
        assert s["serve_swaps"] == 4, s
        assert s["serve_weights_version_last"] == 4, s

    def test_drift_trips_watchdog_and_policy(self, serve_run):
        _, _, _, records = serve_run
        alerts = [r for r in records if r.get("event") == "alert"
                  and r.get("rule") == "serve_drift"]
        assert alerts, "seeded drift never tripped serve_drift"
        assert all(a["round_index"] >= 4 for a in alerts), alerts
        controls = [r for r in records if r.get("event") == "control"
                    and r.get("param") == "serve_swap"]
        assert controls, "act-mode policy never recorded the refresh"
        assert controls[0]["intervention"] == "refresh_serving"
        # the armed refresh lands at the NEXT round boundary and is
        # stamped on that round's serve record
        forced = [r for r in records if r.get("event") == "serve"
                  and r.get("forced_refresh")]
        assert forced, "forced refresh never reached the serving plane"
        assert forced[0]["round_index"] == controls[0]["round_index"] + 1

    def test_tampered_serve_record_fails_replay(self, serve_run):
        _, _, _, records = serve_run
        tampered = []
        for r in records:
            r = dict(r)
            if r.get("event") == "serve" and r.get("round_index") == 5:
                r["weights_version"] += 1
            tampered.append(r)
        errors, _ = replay(tampered)
        assert errors and "diverges" in errors[0], errors


# ----------------------------------------------------------------------
# serving is a read; serving off is the literal seed path


class TestServeOffSeedPath:
    def test_serving_never_perturbs_training(self, data, serve_run):
        cfg_on, s_on, h_on, _ = serve_run
        cfg_off = dataclasses.replace(cfg_on, serve_spec="none",
                                      obs_sinks="memory", obs_dir=None)
        t, s_off, h_off = run_trainer(cfg_off, data)
        assert t._serve_sched is None and t._serve_plane is None
        assert not any(r.get("event") == "serve"
                       for r in t.obs_recorder.memory)
        for a, b in zip(param_leaves(s_on), param_leaves(s_off)):
            np.testing.assert_array_equal(a, b)
        for ra, rb in zip(h_on, h_off):
            assert det_view(ra) == det_view(rb)

    def test_spec_must_name_a_served_engine(self, data):
        # an engine without a serving adapter must refuse the spec
        # loudly, not silently skip the plane
        from federated_pytorch_test_tpu.train.rounds import RoundKernel
        t = BlockwiseFederatedTrainer(TinyNet(), serve_cfg(), data,
                                      AdmmConsensus())
        sched = ServeSchedule.parse(SPEC)
        with pytest.raises(ValueError, match="no serving adapter"):
            RoundKernel._build_serve_plane(t, sched)


# ----------------------------------------------------------------------
# kill/resume: the swap sequence is bitwise across segments


class TestServeKillResume:
    def test_swap_sequence_bitwise_across_restart(self, data, tmp_path,
                                                  serve_run):
        cfg_full, _, _, full_records = serve_run
        done = []

        def bomb(state, rec):
            done.append(1)
            if len(done) == 5:          # dies after completing round 4
                raise Killed

        ck = str(tmp_path / "ck")
        kcfg = dataclasses.replace(cfg_full, obs_sinks="jsonl",
                                   obs_dir=str(tmp_path / "obs"))
        t1 = BlockwiseFederatedTrainer(TinyNet(), kcfg, data,
                                       AdmmConsensus())
        t1.L = 1
        t1.obs_run_name = "seg"
        with pytest.raises(Killed):
            t1.run(log=lambda m: None, checkpoint_path=ck, on_round=bomb)
        t2 = BlockwiseFederatedTrainer(TinyNet(), kcfg, data,
                                       AdmmConsensus())
        t2.L = 1
        t2.obs_run_name = "seg"
        t2.run(log=lambda m: None, checkpoint_path=ck, resume=True)

        records = read_records(str(tmp_path / "obs" / "seg.jsonl"),
                               validate=True)
        errors, stats = replay(records)
        assert not errors, errors
        assert stats["segments"] == 2, stats
        # every serve record — including rounds the resumed segment
        # replayed — carries the same pure fields as the uninterrupted
        # run's record for that round
        want = {r["round_index"]: pure_fields(r) for r in full_records
                if r.get("event") == "serve"}
        got = [r for r in records if r.get("event") == "serve"]
        assert {r["round_index"] for r in got} == set(range(8))
        for rec in got:
            assert pure_fields(rec) == want[rec["round_index"]], rec
