"""VAE / clustering-VAE loss parity and driver smoke tests.

Loss functions are checked against naive numpy re-implementations that follow
the reference's per-sample Python loops literally (federated_vae.py:96-108,
federated_vae_cl.py:101-162).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.train.vae_losses import (
    cost1, cost2, cost21, cost3, vae_cl_loss, vae_loss,
)


class TestVaeLoss:
    def test_matches_naive(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 8, 8, 3)).astype(np.float32)
        r = rng.normal(size=(4, 8, 8, 3)).astype(np.float32)
        mu = rng.normal(size=(4, 10)).astype(np.float32)
        logvar = rng.normal(size=(4, 10)).astype(np.float32)
        got = float(vae_loss(jnp.asarray(r), jnp.asarray(x),
                             jnp.asarray(mu), jnp.asarray(logvar)))
        mse = np.sum((r - x) ** 2)
        kld = -0.5 * np.sum(1 + logvar - mu ** 2 - np.exp(logvar))
        np.testing.assert_allclose(got, mse + kld, rtol=1e-4)


class TestClusteringCosts:
    """Naive loops copied semantically from federated_vae_cl.py:101-140."""

    @pytest.fixture(scope="class")
    def rand(self):
        rng = np.random.default_rng(1)
        B, L = 6, 5
        return dict(
            pk=rng.uniform(0.01, 1, B).astype(np.float32),
            x=rng.normal(size=(B, 4, 4, 3)).astype(np.float32),
            mu_th=rng.normal(size=(B, 4, 4, 3)).astype(np.float32),
            sig2_th=rng.uniform(0.5, 2, (B, 4, 4, 3)).astype(np.float32),
            q_mu=rng.normal(size=(B, L)).astype(np.float32),
            q_sig2=rng.uniform(0.5, 2, (B, L)).astype(np.float32),
            p_mu=rng.normal(size=(B, L)).astype(np.float32),
            p_sig2=rng.uniform(0.5, 2, (B, L)).astype(np.float32),
        )

    def test_cost1(self, rand):
        pk, x, mu, sig2 = rand["pk"], rand["x"], rand["mu_th"], rand["sig2_th"]
        B = x.shape[0]
        naive = 0.0
        for i in range(B):
            err = (x[i] - mu[i]) ** 2 / (2 * sig2[i])
            err1 = 0.5 * np.log(sig2[i] * 2 * math.pi)
            naive += pk[i] * np.sum(err + err1)
        naive /= B
        got = float(cost1(jnp.asarray(pk), jnp.asarray(mu),
                          jnp.asarray(sig2), jnp.asarray(x)))
        np.testing.assert_allclose(got, naive, rtol=1e-4)

    def test_cost2(self, rand):
        pk = rand["pk"]
        naive = float(np.sum(-pk * np.log(pk + 1e-9)) / len(pk))
        np.testing.assert_allclose(float(cost2(jnp.asarray(pk))), naive,
                                   rtol=1e-5)

    def test_cost21(self, rand):
        pk = rand["pk"]
        pbar = pk.mean()
        naive = 1.0 / (-pbar * np.log(pbar + 1e-9) + 1e-9)
        np.testing.assert_allclose(float(cost21(jnp.asarray(pk))), naive,
                                   rtol=1e-5)

    def test_cost3(self, rand):
        pk = rand["pk"]
        B = len(pk)
        naive = 0.0
        for i in range(B):
            mudiff = (rand["p_mu"][i] - rand["q_mu"][i]) ** 2 / rand["p_sig2"][i]
            sigratio = rand["q_sig2"][i] / rand["p_sig2"][i]
            naive += 0.5 * pk[i] * np.sum(
                sigratio - np.log(sigratio) + mudiff - 1)
        naive /= B
        got = float(cost3(jnp.asarray(pk), jnp.asarray(rand["q_mu"]),
                          jnp.asarray(rand["q_sig2"]),
                          jnp.asarray(rand["p_mu"]),
                          jnp.asarray(rand["p_sig2"])))
        np.testing.assert_allclose(got, naive, rtol=1e-4)

    def test_total_loss_combines_terms(self, rand):
        Kc, B = 3, 6
        rng = np.random.default_rng(2)
        ekhat = rng.dirichlet(np.ones(Kc), B).astype(np.float32)
        shape_z = (Kc, B, 5)
        shape_x = (Kc, B, 4, 4, 3)
        args = dict(
            mu_xi=rng.normal(size=shape_z).astype(np.float32),
            sig2_xi=rng.uniform(0.5, 2, shape_z).astype(np.float32),
            mu_b=rng.normal(size=shape_z).astype(np.float32),
            sig2_b=rng.uniform(0.5, 2, shape_z).astype(np.float32),
            mu_th=rng.normal(size=shape_x).astype(np.float32),
            sig2_th=rng.uniform(0.5, 2, shape_x).astype(np.float32),
        )
        x = rng.normal(size=(B, 4, 4, 3)).astype(np.float32)
        total = float(vae_cl_loss(
            jnp.asarray(ekhat), *(jnp.asarray(args[k]) for k in
                                  ("mu_xi", "sig2_xi", "mu_b", "sig2_b",
                                   "mu_th", "sig2_th")), jnp.asarray(x)))
        naive = 0.0
        for k in range(Kc):
            pk = jnp.asarray(ekhat[:, k])
            naive += float(cost1(pk, jnp.asarray(args["mu_th"][k]),
                                 jnp.asarray(args["sig2_th"][k]),
                                 jnp.asarray(x)))
            naive += 10.0 * (float(cost2(pk))
                             + float(cost3(pk, jnp.asarray(args["mu_xi"][k]),
                                           jnp.asarray(args["sig2_xi"][k]),
                                           jnp.asarray(args["mu_b"][k]),
                                           jnp.asarray(args["sig2_b"][k]))))
            naive += float(cost21(pk))
        np.testing.assert_allclose(total, naive, rtol=1e-4)


class TestWeightedPartialBatch:
    """Weighted losses on a wrap-padded batch equal the plain losses on the
    true partial batch (drop_last=False parity, federated_multi.py:74-83)."""

    def test_vae_loss_weighted(self):
        rng = np.random.default_rng(3)
        B, real = 8, 5                   # 3 pad rows wrap-copy rows 0..2
        x = rng.normal(size=(B, 4, 4, 3)).astype(np.float32)
        r = rng.normal(size=(B, 4, 4, 3)).astype(np.float32)
        mu = rng.normal(size=(B, 6)).astype(np.float32)
        logvar = rng.normal(size=(B, 6)).astype(np.float32)
        w = np.zeros(B, np.float32)
        w[:real] = 1.0
        padded = float(vae_loss(jnp.asarray(r), jnp.asarray(x),
                                jnp.asarray(mu), jnp.asarray(logvar),
                                jnp.asarray(w)))
        true = float(vae_loss(jnp.asarray(r[:real]), jnp.asarray(x[:real]),
                              jnp.asarray(mu[:real]),
                              jnp.asarray(logvar[:real])))
        np.testing.assert_allclose(padded, true, rtol=1e-5)

    def test_vae_cl_loss_weighted(self):
        Kc, B, real = 3, 8, 5
        rng = np.random.default_rng(4)
        ekhat = rng.dirichlet(np.ones(Kc), B).astype(np.float32)
        shape_z = (Kc, B, 5)
        shape_x = (Kc, B, 4, 4, 3)
        args = [rng.normal(size=shape_z).astype(np.float32),
                rng.uniform(0.5, 2, shape_z).astype(np.float32),
                rng.normal(size=shape_z).astype(np.float32),
                rng.uniform(0.5, 2, shape_z).astype(np.float32),
                rng.normal(size=shape_x).astype(np.float32),
                rng.uniform(0.5, 2, shape_x).astype(np.float32)]
        x = rng.normal(size=(B, 4, 4, 3)).astype(np.float32)
        w = np.zeros(B, np.float32)
        w[:real] = 1.0
        padded = float(vae_cl_loss(
            jnp.asarray(ekhat), *(jnp.asarray(a) for a in args),
            jnp.asarray(x), w=jnp.asarray(w)))
        true = float(vae_cl_loss(
            jnp.asarray(ekhat[:real]),
            *(jnp.asarray(a[:, :real]) for a in args),
            jnp.asarray(x[:real])))
        np.testing.assert_allclose(padded, true, rtol=1e-5)


@pytest.mark.slow
class TestVaeDrivers:
    def test_vae_driver_smoke(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        from federated_pytorch_test_tpu.drivers.federated_vae import main
        # n-train 40 / batch 16 -> 2 full batches + a wrap-padded remainder
        # of 8, exercising the weighted partial-minibatch path end-to-end
        state, hist = main(["--K", "2", "--Nloop", "1", "--Nadmm", "1",
                            "--n-train", "40", "--n-test", "32",
                            "--default-batch", "16", "--no-save-model"])
        assert len(hist) == 12          # 12 layer sweeps x 1 round
        assert all(np.isfinite(h["loss"]) for h in hist)

    def test_vae_cl_driver_smoke(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        from federated_pytorch_test_tpu.drivers.federated_vae_cl import main
        # remainder batch included (40 = 2x16 + 8) — covers the LBFGS
        # blocks' weighted-closure path too
        state, hist = main(["--K", "2", "--Nloop", "1", "--Nadmm", "1",
                            "--n-train", "40", "--n-test", "32",
                            "--default-batch", "16", "--Kc", "3", "--Lc", "4",
                            "--no-save-model"])
        assert len(hist) == 3           # enc / dec / latent blocks
        assert all(np.isfinite(h["loss"]) for h in hist)
